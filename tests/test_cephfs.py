"""MDS + CephFS-lite client.

Mirrors the reference's fs test strategy (qa/workunits/fs + client
tests): namespace operations, file IO through striped data objects,
persistence across MDS restart, and multiple clients sharing one tree.
"""

import asyncio
import sys

import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster, make_ctx  # noqa: E402

from ceph_tpu.msg.messenger import Messenger  # noqa: E402
from ceph_tpu.msg.types import EntityName  # noqa: E402
from ceph_tpu.services.cephfs import CephFS, CephFSError  # noqa: E402
from ceph_tpu.services.mds import MDS  # noqa: E402


async def _start_mds(cl, admin, mds_id="a"):
    for pool in ("cephfs_metadata", "cephfs_data"):
        if admin.monc.osdmap.lookup_pool(pool) < 0:
            await admin.pool_create(pool, pg_num=8)
    ctx = make_ctx(f"mds.{mds_id}")
    r = await cl.client(name=f"mds.{mds_id}")
    msgr = Messenger(ctx, EntityName("mds", mds_id))
    addr = await msgr.bind()
    mds = MDS(ctx, msgr, r, "cephfs_metadata")
    await mds.create_fs()
    await mds.start()
    return mds, msgr, addr


def test_cephfs_namespace_and_file_io():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        mds, msgr, addr = await _start_mds(cl, admin)
        fs = CephFS(admin, addr, "cephfs_data")

        # tree building
        await fs.makedirs("/home/alice/projects")
        await fs.mkdir("/tmp")
        with pytest.raises(CephFSError):
            await fs.mkdir("/home")                  # EEXIST
        assert await fs.listdir("/") == ["home", "tmp"]
        assert await fs.listdir("/home") == ["alice"]

        # file io across stripe boundaries
        payload = bytes(range(256)) * 4096           # 1 MiB
        await fs.write_file("/home/alice/projects/data.bin", payload)
        assert await fs.read_file("/home/alice/projects/data.bin") \
            == payload
        st = await fs.stat("/home/alice/projects/data.bin")
        assert st["size"] == len(payload) and st["type"] == "file"

        # handle-level io: append + positioned read
        f = await fs.open("/log.txt", "w")
        await f.write(b"line1\n")
        await f.write(b"line2\n")
        await f.close()
        f = await fs.open("/log.txt", "a")
        await f.write(b"line3\n")
        await f.close()
        f = await fs.open("/log.txt", "r")
        assert await f.read() == b"line1\nline2\nline3\n"
        assert await f.read(5, offset=6) == b"line2"
        await f.close()

        # rename + unlink + rmdir
        await fs.rename("/log.txt", "/tmp/log-moved.txt")
        assert "log.txt" not in await fs.listdir("/")
        assert await fs.read_file("/tmp/log-moved.txt") \
            == b"line1\nline2\nline3\n"
        await fs.unlink("/tmp/log-moved.txt")
        with pytest.raises(CephFSError):
            await fs.read_file("/tmp/log-moved.txt")
        with pytest.raises(CephFSError):
            await fs.rmdir("/home/alice")            # not empty
        await fs.rmdir("/tmp")
        assert await fs.listdir("/") == ["home"]

        # data objects are actually striped into the data pool
        names = await admin.open_ioctx("cephfs_data").list_objects()
        assert names, "file data must live in the data pool"
        await msgr.shutdown()
        await cl.stop()
    asyncio.run(run())


def test_cephfs_metadata_survives_mds_restart():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        mds, msgr, addr = await _start_mds(cl, admin)
        fs = CephFS(admin, addr, "cephfs_data")
        await fs.makedirs("/deep/tree")
        await fs.write_file("/deep/tree/file", b"persistent")
        # kill the MDS; a NEW MDS over the same pools serves the tree
        await msgr.shutdown()
        mds2, msgr2, addr2 = await _start_mds(cl, admin, mds_id="b")
        fs2 = CephFS(admin, addr2, "cephfs_data")
        assert await fs2.listdir("/deep") == ["tree"]
        assert await fs2.read_file("/deep/tree/file") == b"persistent"
        # and inode allocation continues without collisions
        await fs2.write_file("/deep/tree/new", b"post-restart")
        a = await fs2.stat("/deep/tree/file")
        b = await fs2.stat("/deep/tree/new")
        assert a["ino"] != b["ino"]
        await msgr2.shutdown()
        await cl.stop()
    asyncio.run(run())


def test_cephfs_two_clients_share_namespace():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        mds, msgr, addr = await _start_mds(cl, admin)
        c1 = CephFS(admin, addr, "cephfs_data")
        other = await cl.client(name="client.two")
        c2 = CephFS(other, addr, "cephfs_data")
        await c1.mkdir("/shared")
        await c1.write_file("/shared/note", b"from c1")
        assert await c2.read_file("/shared/note") == b"from c1"
        await c2.write_file("/shared/note", b"c2 overwrote")
        assert await c1.read_file("/shared/note") == b"c2 overwrote"
        # concurrent creates allocate distinct inodes
        await asyncio.gather(*[
            c1.write_file(f"/shared/a{i}", b"x") for i in range(8)
        ], *[
            c2.write_file(f"/shared/b{i}", b"y") for i in range(8)
        ])
        ents = await c1.listdir("/shared")
        assert len(ents) == 17
        inos = {(await c1.stat(f"/shared/{e}"))["ino"] for e in ents}
        assert len(inos) == 17
        await msgr.shutdown()
        await cl.stop()
    asyncio.run(run())


def test_mdlog_crash_recovery_replays_unflushed_mutations():
    """MDLog role (mds/MDLog.cc): mutations are acked once journaled;
    an MDS that dies BEFORE write-back must lose nothing — a fresh MDS
    replays the journal into omap on start."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        # huge flush thresholds: nothing reaches omap before the crash
        ctx = make_ctx("mds.a")
        r = await cl.client(name="mds.a")
        for pool in ("cephfs_metadata", "cephfs_data"):
            if admin.monc.osdmap.lookup_pool(pool) < 0:
                await admin.pool_create(pool, pg_num=8)
        msgr = Messenger(ctx, EntityName("mds", "a"))
        addr = await msgr.bind()
        mds = MDS(ctx, msgr, r, "cephfs_metadata",
                  log_flush_interval=3600.0, log_flush_events=10**9)
        await mds.create_fs()
        await mds.start()
        fs = CephFS(admin, addr, "cephfs_data")
        await fs.makedirs("/deep/tree")
        await fs.write_file("/deep/tree/f.txt", b"journaled bytes")
        await fs.rename("/deep/tree/f.txt", "/deep/tree/g.txt")
        # CRASH: tear down the messenger without flushing the MDLog
        if mds._flush_task is not None:
            mds._flush_task.cancel()
        await msgr.shutdown()
        # omap must NOT yet hold the entries (they were only journaled)
        from ceph_tpu.services.mds import ROOT_INO, dir_oid
        meta_io = admin.open_ioctx("cephfs_metadata")
        root = await meta_io.omap_get(dir_oid(ROOT_INO))
        assert b"deep" not in root, "write-back flushed too early"

        # a fresh MDS on the same pool replays the journal
        ctx2 = make_ctx("mds.b")
        r2 = await cl.client(name="mds.b")
        msgr2 = Messenger(ctx2, EntityName("mds", "b"))
        addr2 = await msgr2.bind()
        mds2 = MDS(ctx2, msgr2, r2, "cephfs_metadata")
        await mds2.create_fs()
        await mds2.start()      # replay happens here
        fs2 = CephFS(admin, addr2, "cephfs_data")
        assert await fs2.read_file("/deep/tree/g.txt") \
            == b"journaled bytes"
        assert sorted(await fs2.listdir("/deep/tree")) == ["g.txt"]
        await mds2.stop()
        await msgr2.shutdown()
        await cl.stop()
    asyncio.run(run())


def test_dentry_leases_cache_and_revoke():
    """Client-caps fast path (Locker.cc leases): repeated stats are
    served from the lease cache; a SECOND client's mutation revokes the
    first client's lease so it re-fetches fresh metadata."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        mds, msgr, addr = await _start_mds(cl, admin)
        fs1 = CephFS(admin, addr, "cephfs_data")
        # second mount on its OWN messenger/identity
        c2 = await cl.client(name="client.m2")
        fs2 = CephFS(c2, addr, "cephfs_data")

        await fs1.write_file("/doc.txt", b"version one")
        ent1 = await fs1.stat("/doc.txt")
        hits0 = fs1.lease_hits
        ent1b = await fs1.stat("/doc.txt")      # served by the lease
        assert fs1.lease_hits == hits0 + 1 and ent1b == ent1

        # fs2 rewrites the file: fs1's lease must be revoked
        await fs2.write_file("/doc.txt", b"version two, longer")
        for _ in range(50):
            if "/doc.txt" not in fs1._leases:
                break
            await asyncio.sleep(0.05)
        assert "/doc.txt" not in fs1._leases, "lease never revoked"
        ent2 = await fs1.stat("/doc.txt")       # fresh RPC
        assert ent2["size"] == len(b"version two, longer")
        assert await fs1.read_file("/doc.txt") == b"version two, longer"
        await cl.stop()
    asyncio.run(run())


async def _start_ranks(cl, admin, n):
    """Boot an n-rank MDS cluster and wire peer addresses."""
    for pool in ("cephfs_metadata", "cephfs_data"):
        if admin.monc.osdmap.lookup_pool(pool) < 0:
            await admin.pool_create(pool, pg_num=8)
    ranks = []
    for rk in range(n):
        ctx = make_ctx(f"mds.r{rk}")
        r = await cl.client(name=f"mds.r{rk}")
        msgr = Messenger(ctx, EntityName("mds", f"r{rk}"))
        addr = await msgr.bind()
        mds = MDS(ctx, msgr, r, "cephfs_metadata", rank=rk, nranks=n)
        if rk == 0:
            await mds.create_fs()
        await mds.start()
        ranks.append((mds, msgr, addr))
    for mds, _, _ in ranks:
        mds.peers = {rk: a for rk, (_, _, a) in enumerate(ranks)}
    return ranks


def test_multirank_namespace_spans_ranks():
    """A 3-rank MDS cluster: dirs land on their computed owner rank,
    the full namespace works through per-component walks, and inos
    allocated by different ranks never collide (cls ino blocks)."""
    from ceph_tpu.services.mds import owner_rank

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        ranks = await _start_ranks(cl, admin, 3)
        addrs = [a for _, _, a in ranks]
        fs = CephFS(admin, addrs, "cephfs_data")

        # build a tree wide enough to hit every rank
        inos = {}
        for i in range(12):
            await fs.makedirs(f"/d{i}/sub")
            inos[f"/d{i}"] = (await fs.stat(f"/d{i}"))["ino"]
        owners = {owner_rank(v, 3) for v in inos.values()}
        assert owners == {0, 1, 2}         # partition actually spreads
        assert len(set(inos.values())) == len(inos)   # no dup inos

        # file io across subtrees
        await fs.write_file("/d3/sub/f.bin", b"across-ranks" * 500)
        assert await fs.read_file("/d3/sub/f.bin") == b"across-ranks" * 500
        assert await fs.listdir("/d3/sub") == ["f.bin"]

        # unlink + rmdir chain through different owners
        await fs.unlink("/d3/sub/f.bin")
        await fs.rmdir("/d3/sub")
        with pytest.raises(CephFSError):
            await fs.listdir("/d3/sub")
        # parent dentry gone too
        assert await fs.listdir("/d3") == []

        for mds, msgr, _ in ranks:
            await mds.stop()
            await msgr.shutdown()
        await cl.stop()
    asyncio.run(run())


def test_multirank_cross_rank_rename_and_rmdir():
    """Rename between directories owned by DIFFERENT ranks (peer
    lookup + conditional unlink) and rmdir of a child dir owned
    elsewhere (peer emptiness check)."""
    from ceph_tpu.services.mds import owner_rank

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        ranks = await _start_ranks(cl, admin, 2)
        addrs = [a for _, _, a in ranks]
        fs = CephFS(admin, addrs, "cephfs_data")

        # find two top-level dirs with different owner ranks
        names, inos = [], {}
        i = 0
        while len({owner_rank(v, 2) for v in inos.values()}) < 2:
            nm = f"/x{i}"
            await fs.mkdir(nm)
            inos[nm] = (await fs.stat(nm))["ino"]
            i += 1
        a, b = sorted(inos, key=lambda n: owner_rank(inos[n], 2))[0], \
            sorted(inos, key=lambda n: owner_rank(inos[n], 2))[-1]
        assert owner_rank(inos[a], 2) != owner_rank(inos[b], 2)

        await fs.write_file(f"{a}/moveme", b"M" * 4096)
        await fs.rename(f"{a}/moveme", f"{b}/moved")
        assert await fs.read_file(f"{b}/moved") == b"M" * 4096
        with pytest.raises(CephFSError):
            await fs.stat(f"{a}/moveme")
        # rename onto an existing file replaces it
        await fs.write_file(f"{a}/other", b"O")
        await fs.rename(f"{b}/moved", f"{a}/other")
        assert await fs.read_file(f"{a}/other") == b"M" * 4096

        # rmdir where the child dir's owner differs from the parent's:
        # mkdir under b until the CHILD ino is owned by the other rank
        j = 0
        while True:
            nm = f"{b}/c{j}"
            await fs.mkdir(nm)
            cino = (await fs.stat(nm))["ino"]
            if owner_rank(cino, 2) != owner_rank(inos[b], 2):
                break
            j += 1
        # non-empty: refused (emptiness checked by the child's owner)
        await fs.write_file(f"{nm}/keep", b"k")
        with pytest.raises(CephFSError):
            await fs.rmdir(nm)
        await fs.unlink(f"{nm}/keep")
        await fs.rmdir(nm)
        with pytest.raises(CephFSError):
            await fs.stat(nm)

        for mds, msgr, _ in ranks:
            await mds.stop()
            await msgr.shutdown()
        await cl.stop()
    asyncio.run(run())


def test_multirank_lease_revoke_and_restart_replay():
    """Dentry leases stay coherent across ranks (each dentry's leases
    live only at its owner), and a rank crash replays ITS OWN mdlog."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        ranks = await _start_ranks(cl, admin, 2)
        addrs = [a for _, _, a in ranks]
        c2r = await cl.client(name="client.c2")
        fs1 = CephFS(admin, addrs, "cephfs_data")
        fs2 = CephFS(c2r, addrs, "cephfs_data")

        await fs1.makedirs("/share")
        await fs1.write_file("/share/doc", b"v1")
        # both clients cache the dentry
        assert (await fs2.stat("/share/doc"))["size"] == 2
        before = fs2.lease_hits
        await fs2.stat("/share/doc")
        # per-component walk: both "share" and "doc" served from lease
        assert fs2.lease_hits == before + 2
        # fs1 mutates: fs2's lease must be revoked
        f = await fs1.open("/share/doc", "w")
        await f.write(b"version-two")
        await f.close()
        await asyncio.sleep(0.05)                # revoke delivery
        assert (await fs2.stat("/share/doc"))["size"] == 11

        # crash a rank WITHOUT flush: restart replays its own journal
        from ceph_tpu.services.mds import owner_rank
        ino = (await fs1.stat("/share"))["ino"]
        rk = owner_rank(ino, 2)
        mds, msgr, addr = ranks[rk]
        await fs1.write_file("/share/unflushed", b"U" * 100)
        if mds._flush_task is not None:          # crash: no flush
            mds._flush_task.cancel()
            mds._flush_task = None
        await msgr.shutdown()
        ctx = make_ctx(f"mds.r{rk}b")
        r = await cl.client(name=f"mds.r{rk}b")
        msgr2 = Messenger(ctx, EntityName("mds", f"r{rk}b"))
        addr2 = await msgr2.bind()
        mds2 = MDS(ctx, msgr2, r, "cephfs_metadata", rank=rk, nranks=2)
        await mds2.start()
        addrs2 = list(addrs)
        addrs2[rk] = addr2
        mds2.peers = {i: a for i, a in enumerate(addrs2)}
        other = ranks[1 - rk][0]
        other.peers = dict(mds2.peers)
        fs3 = CephFS(admin, addrs2, "cephfs_data")
        assert await fs3.read_file("/share/unflushed") == b"U" * 100

        await mds2.stop()
        await msgr2.shutdown()
        for i, (mds_, msgr_, _) in enumerate(ranks):
            if i != rk:
                await mds_.stop()
                await msgr_.shutdown()
        await cl.stop()
    asyncio.run(run())


def test_cephfs_snapshots_end_to_end():
    """CephFS snapshots (mds/SnapServer + snaprealm distilled): mkdir
    /d/.snap/<name> freezes the subtree; post-snap writes COW the
    data-pool clones; .snap reads serve the frozen bytes; unlink of
    the live file leaves the snapshot readable; rmsnap retires it."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        mds, msgr, addr = await _start_mds(cl, admin)
        fs = CephFS(admin, addr, "cephfs_data")

        await fs.makedirs("/proj/sub")
        v1 = b"version-one " * 5000          # striped size
        await fs.write_file("/proj/data.bin", v1)
        await fs.write_file("/proj/sub/notes.txt", b"alpha")

        # snapshot via the .snap virtual dir
        await fs.mkdir("/proj/.snap/s1")
        assert await fs.listdir("/proj/.snap") == ["s1"]

        # overwrite + new file AFTER the snapshot
        v2 = b"version-two!" * 6000
        await fs.write_file("/proj/data.bin", v2)
        await fs.write_file("/proj/later.txt", b"not in snap")

        # live tree serves v2; the snapshot serves v1
        assert await fs.read_file("/proj/data.bin") == v2
        assert await fs.read_file("/proj/.snap/s1/data.bin") == v1
        assert await fs.read_file("/proj/.snap/s1/sub/notes.txt") \
            == b"alpha"
        # snapshot listing is the frozen namespace
        assert await fs.listdir("/proj/.snap/s1") \
            == ["data.bin", "sub"]
        assert await fs.listdir("/proj/.snap/s1/sub") == ["notes.txt"]
        st = await fs.stat("/proj/.snap/s1/data.bin")
        assert st["size"] == len(v1)
        with pytest.raises(CephFSError):
            await fs.read_file("/proj/.snap/s1/later.txt")  # post-snap

        # snapshots are read-only
        with pytest.raises(CephFSError):
            await fs.write_file("/proj/.snap/s1/data.bin", b"x")
        with pytest.raises(CephFSError):
            await fs.unlink("/proj/.snap/s1/data.bin")
        # '.snap' itself is an unusable file name
        with pytest.raises(CephFSError):
            await fs.mkdir("/proj/sub/.snap/nested/deep")

        # deleting the LIVE file keeps the snapshot readable
        await fs.unlink("/proj/data.bin")
        with pytest.raises(CephFSError):
            await fs.read_file("/proj/data.bin")
        assert await fs.read_file("/proj/.snap/s1/data.bin") == v1

        # second snapshot sees the current (post-delete) tree
        await fs.mksnap("/proj", "s2")
        assert sorted(await fs.listdir("/proj/.snap")) == ["s1", "s2"]
        assert await fs.listdir("/proj/.snap/s2") \
            == ["later.txt", "sub"]

        # a dir with live snapshots refuses rmdir (the snap records
        # anchor there; removing them would leak snapids forever)
        await fs.mkdir("/victim")
        await fs.mksnap("/victim", "sv")
        with pytest.raises(CephFSError):
            await fs.rmdir("/victim")
        await fs.rmsnap("/victim", "sv")
        await fs.rmdir("/victim")

        # rmsnap via rmdir of the virtual path
        await fs.rmdir("/proj/.snap/s1")
        assert await fs.listdir("/proj/.snap") == ["s2"]
        with pytest.raises(CephFSError):
            await fs.read_file("/proj/.snap/s1/data.bin")

        await mds.stop()
        await msgr.shutdown()
        await cl.stop()
    asyncio.run(run())


def test_multirank_snapshot_spans_ranks():
    """mksnap on a subtree whose child dirs are owned by OTHER ranks:
    the manifest walk rides peer_readdir (capturing peers' unflushed
    caches) and concurrent mksnaps on different ranks never lose each
    other's snapid (atomic cls snap table)."""
    from ceph_tpu.services.mds import owner_rank

    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        ranks = await _start_ranks(cl, admin, 3)
        addrs = [a for _, _, a in ranks]
        fs = CephFS(admin, addrs, "cephfs_data")

        # find two sibling dirs owned by DIFFERENT ranks
        made, owners = [], {}
        for i in range(8):
            await fs.mkdir(f"/m{i}")
            ino = (await fs.stat(f"/m{i}"))["ino"]
            owners[f"/m{i}"] = owner_rank(ino, 3)
            made.append(f"/m{i}")
        root_owner = owner_rank(1, 3)
        cross = next(p for p in made if owners[p] != root_owner)
        await fs.write_file(f"{cross}/f.txt", b"cross-rank bytes")

        # snapshot the ROOT: the walk must traverse dirs on all ranks
        await fs.mksnap("/", "all")
        assert await fs.read_file(f"/.snap/all{cross}/f.txt") \
            == b"cross-rank bytes"

        # concurrent snapshots on dirs owned by different ranks: both
        # snapids must survive in the table (every client write COWs
        # both) — the atomic cls update is what makes this hold
        a_dir = next(p for p in made if owners[p] == root_owner)
        await asyncio.gather(fs.mksnap(cross, "c1"),
                             fs.mksnap(a_dir, "c2"))
        _, seq, ids = await ranks[0][0]._snap_table(force=True)
        assert len(ids) >= 3           # "all" + "c1" + "c2"

        # post-snap write; per-dir snapshot still serves the old bytes
        await fs.write_file(f"{cross}/f.txt", b"NEW")
        assert await fs.read_file(f"{cross}/.snap/c1/f.txt") \
            == b"cross-rank bytes"

        for mds, msgr, _ in ranks:
            await mds.stop()
            await msgr.shutdown()
        await cl.stop()
    asyncio.run(run())
