"""Cross-PG device batch collector (osd/ec_queue.py).

Unit: coalescing, correctness vs the host kernel, host-fallback policy,
perf accounting.  E2E: a live in-process cluster with
osd_ec_batch_device=on proves client writes on an EC pool flow through
the device queue (device_bytes > 0 on the primary, results readable).
The jit path runs on the CPU backend here; the identical code hits the
fused pallas kernel on TPU.
"""

import asyncio
import sys

import numpy as np
import pytest

from ceph_tpu.common.context import Context
from ceph_tpu.ec import gf256
from ceph_tpu.osd.ec_queue import ECBatchQueue


def make_queue(mode="force", window_ms=5.0, min_device_bytes=1 << 16):
    ctx = Context("osd.0")
    return ECBatchQueue(ctx, mode=mode, window_ms=window_ms,
                        min_device_bytes=min_device_bytes)


def gen_mat(k=4, m=2):
    return gf256.rs_vandermonde_matrix(k, m)[k:]


def test_concurrent_requests_coalesce_into_one_launch():
    async def run():
        q = make_queue(min_device_bytes=256)
        mat = gen_mat()
        rng = np.random.default_rng(0)
        ins = [rng.integers(0, 256, (4, 1000 + 128 * i), dtype=np.uint8)
               for i in range(8)]
        outs = await asyncio.gather(*[q.apply(mat, c) for c in ins])
        for c, o in zip(ins, outs):
            assert np.array_equal(o, gf256.host_apply(mat, c))
        d = q.perf.dump()
        assert d["device_requests"] == 8
        assert d["device_launches"] == 1          # ONE folded launch
        assert d["device_bytes"] == sum(4 * c.shape[1] for c in ins)
        await q.stop()
    asyncio.run(run())


def test_mixed_matrices_group_separately():
    async def run():
        q = make_queue(min_device_bytes=256)
        m1, m2 = gen_mat(4, 2), gen_mat(2, 1)
        rng = np.random.default_rng(1)
        c1 = rng.integers(0, 256, (4, 3000), dtype=np.uint8)
        c2 = rng.integers(0, 256, (2, 5000), dtype=np.uint8)
        o1, o2 = await asyncio.gather(q.apply(m1, c1), q.apply(m2, c2))
        assert np.array_equal(o1, gf256.host_apply(m1, c1))
        assert np.array_equal(o2, gf256.host_apply(m2, c2))
        assert q.perf.dump()["device_launches"] == 2
        await q.stop()
    asyncio.run(run())


def test_small_lone_request_takes_host_path():
    async def run():
        q = make_queue(min_device_bytes=1 << 20)
        mat = gen_mat()
        c = np.arange(4 * 512, dtype=np.uint8).reshape(4, 512)
        out = await q.apply(mat, c)
        assert np.array_equal(out, gf256.host_apply(mat, c))
        d = q.perf.dump()
        assert d["host_requests"] == 1 and d["device_requests"] == 0
        await q.stop()
    asyncio.run(run())


def test_oversize_batch_splits_into_bucket_windows():
    # total lanes beyond the largest bucket: must split into multiple
    # launches, not fail over to the host path
    from ceph_tpu.osd import ec_queue as eq

    async def run():
        q = make_queue(min_device_bytes=256)
        mat = gen_mat(2, 1)
        cap = eq.LANE_BUCKETS[-1]
        rng = np.random.default_rng(9)
        c = rng.integers(0, 256, (2, cap + 12345), dtype=np.uint8)
        out = await q.apply(mat, c)
        assert np.array_equal(out, gf256.host_apply(mat, c))
        d = q.perf.dump()
        assert d["device_launches"] == 2 and d["host_requests"] == 0
        await q.stop()
    asyncio.run(run())


def test_mode_on_bypasses_device_on_cpu_backend():
    """mode=on requires a real accelerator: on the CPU jax backend the
    device path only adds dispatch+window latency over the native SIMD
    kernel (round-4 bench: 3.4x e2e regression), so requests must route
    straight to the host."""
    async def run():
        q = make_queue(mode="on", min_device_bytes=256)
        mat = gen_mat()
        c = np.arange(4 * (1 << 17), dtype=np.uint8).reshape(4, -1) \
            .astype(np.uint8)
        out = await q.apply(mat, c)
        assert np.array_equal(out, gf256.host_apply(mat, c))
        d = q.perf.dump()
        assert d["host_requests"] == 1 and d["device_requests"] == 0
        await q.stop()
    asyncio.run(run())


def test_bytes_quorum_flushes_before_window():
    """A batch that reaches flush_bytes must launch immediately instead
    of sitting out the full fill window."""
    import time

    async def run():
        q = make_queue(window_ms=500.0, min_device_bytes=256)
        q.flush_bytes = 1 << 12
        mat = gen_mat()
        c = np.arange(4 * (1 << 14), dtype=np.uint8).reshape(4, -1) \
            .astype(np.uint8)
        t0 = time.perf_counter()
        out = await q.apply(mat, c)
        dt = time.perf_counter() - t0
        assert np.array_equal(out, gf256.host_apply(mat, c))
        assert q.perf.dump()["device_requests"] == 1
        assert dt < 0.4, f"quorum flush took {dt:.3f}s (window stall)"
        await q.stop()
    asyncio.run(run())


def test_mode_off_never_touches_device():
    async def run():
        q = make_queue(mode="off")
        mat = gen_mat()
        c = np.arange(4 * 100000, dtype=np.uint8).reshape(4, -1) & 0xFF
        c = c.astype(np.uint8)
        out = await q.apply(mat, c)
        assert np.array_equal(out, gf256.host_apply(mat, c))
        assert q.perf.dump()["device_requests"] == 0
        await q.stop()
    asyncio.run(run())


def test_device_failure_falls_back_to_host(monkeypatch):
    async def run():
        q = make_queue(min_device_bytes=256)

        def boom(reqs):
            raise RuntimeError("device gone")
        monkeypatch.setattr(q, "_run_group", boom)
        mat = gen_mat()
        c = np.arange(4 * (1 << 17), dtype=np.uint8).reshape(4, -1) \
            .astype(np.uint8)
        out = await q.apply(mat, c)
        assert np.array_equal(out, gf256.host_apply(mat, c))
        assert q.perf.dump()["host_requests"] == 1
        await q.stop()
    asyncio.run(run())


def test_ec_pool_writes_ride_the_device_queue():
    """E2E: cluster with osd_ec_batch_device=on — concurrent EC writes
    coalesce on the primary's device queue and read back intact."""
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_osd import Cluster, FAST_CFG
    saved = dict(FAST_CFG)
    FAST_CFG["osd_ec_batch_device"] = "force"
    FAST_CFG["osd_ec_batch_min_bytes"] = 1024
    try:
        async def run():
            cl = Cluster()
            admin = await cl.start(6)
            await admin.pool_create("ecpool", pg_num=8,
                                    pool_type="erasure", k=4, m=2)
            io = admin.open_ioctx("ecpool")
            rng = np.random.default_rng(3)
            payloads = {f"obj{i}": rng.integers(
                0, 256, 16384 + 512 * i, dtype=np.uint8).tobytes()
                for i in range(6)}
            await asyncio.gather(*[io.write_full(k, v)
                                   for k, v in payloads.items()])
            for k, v in payloads.items():
                assert await io.read(k) == v
            stats = [osd.ec_queue.perf.dump() for osd in cl.osds.values()]
            total_dev = sum(s["device_bytes"] for s in stats)
            total_reqs = sum(s["device_requests"] for s in stats)
            launches = sum(s["device_launches"] for s in stats)
            assert total_reqs == len(payloads)
            assert total_dev > 0
            assert launches <= total_reqs     # coalescing may merge them
            await cl.stop()
        asyncio.run(run())
    finally:
        FAST_CFG.clear()
        FAST_CFG.update(saved)
