"""Tool CLI tests: crushtool, osdmaptool, ec_benchmark in-process, plus a
real-subprocess vstart cluster exercise (ceph-helpers.sh role: run
daemons, put/get via CLI mains, kill a daemon, keep serving).
"""

import asyncio
import json
import os
import signal
import sys

import pytest

from ceph_tpu.tools import crushtool, ec_benchmark, osdmaptool


def test_crushtool_build_test_decompile(tmp_path, capsys):
    mapfile = str(tmp_path / "cm.bin")
    assert crushtool.main(["--build", "8", "--osds-per-host", "2",
                           "-o", mapfile]) == 0
    assert crushtool.main(["--test", mapfile, "--num-rep", "3",
                           "--max-x", "127", "--json"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    rep = json.loads(out[-1])
    assert rep["inputs"] == 128
    assert rep["result_size_histogram"] == {"3": 128} or \
        rep["result_size_histogram"] == {3: 128}
    assert crushtool.main(["-d", mapfile]) == 0
    out = capsys.readouterr().out
    assert "bucket host0" in out and "rule replicated_rule" in out


def test_osdmaptool_test_map_pgs(tmp_path, capsys):
    sys.path.insert(0, os.path.dirname(__file__))
    from test_osdmap import build_map
    m = build_map()
    mapfile = str(tmp_path / "om.bin")
    with open(mapfile, "wb") as f:
        f.write(m.to_bytes())
    assert osdmaptool.main([mapfile, "--test-map-pgs", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["total_pgs"] == 64            # 2 pools x 32
    assert rep["pg_per_osd"]["min"] > 0
    assert osdmaptool.main([mapfile, "--print"]) == 0
    out = capsys.readouterr().out
    assert "pool 1" in out and "osd.0" in out


def test_ec_benchmark_contract(capsys):
    assert ec_benchmark.main(
        ["--plugin", "rs", "--workload", "encode", "--size", "262144",
         "--iterations", "2", "-P", "k=4", "-P", "m=2",
         "-P", "backend=host", "--json"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    secs, kib = lines[0].split("\t")       # reference print contract
    assert float(secs) > 0 and int(kib) == 512
    rep = json.loads(lines[1])
    assert rep["k"] == 4 and rep["m"] == 2
    # decode with erasures verifies reconstruction internally
    assert ec_benchmark.main(
        ["--plugin", "rs", "--workload", "decode", "--size", "262144",
         "--iterations", "2", "--erasures", "2", "-P", "k=4", "-P", "m=2",
         "-P", "backend=host"]) == 0


@pytest.mark.slow
def test_vstart_subprocess_cluster(tmp_path):
    """Full operator path with real daemon subprocesses."""
    from ceph_tpu.tools.vstart import VCluster
    from ceph_tpu.tools import rados as rados_cli
    from ceph_tpu.tools import ceph as ceph_cli

    d = str(tmp_path / "cl")
    cl = VCluster(d, n_osds=3, n_mons=1,
                  conf={"osd_heartbeat_grace": "3.0",
                        "mon_osd_down_out_interval": "5.0"})
    cl.write_configs()
    cl.start_daemons()
    try:
        asyncio.run(cl.bootstrap())
        assert ceph_cli.main(["--dir", d, "osd", "pool", "create",
                              "data", "8"]) == 0
        obj = str(tmp_path / "payload")
        with open(obj, "wb") as f:
            f.write(b"vstart-payload" * 100)
        out = str(tmp_path / "out")
        assert rados_cli.main(["--dir", d, "-p", "data", "put", "obj1",
                               obj]) == 0
        assert rados_cli.main(["--dir", d, "-p", "data", "get", "obj1",
                               out]) == 0
        assert open(out, "rb").read() == b"vstart-payload" * 100
        # kill one osd (kill_daemon role); reads must keep working once
        # failure detection + remap kick in
        cl.kill_daemon("osd.2", signal.SIGKILL)

        async def read_until_ok():
            admin = await cl.admin()
            try:
                io = admin.open_ioctx("data")
                deadline = asyncio.get_event_loop().time() + 60
                while True:
                    try:
                        data = await io.read("obj1")
                        return data
                    except Exception:
                        assert asyncio.get_event_loop().time() < deadline
                        await asyncio.sleep(0.5)
            finally:
                await admin.shutdown()
        assert asyncio.run(read_until_ok()) == b"vstart-payload" * 100
    finally:
        cl.stop()
