"""Tool CLI tests: crushtool, osdmaptool, ec_benchmark in-process, plus a
real-subprocess vstart cluster exercise (ceph-helpers.sh role: run
daemons, put/get via CLI mains, kill a daemon, keep serving).
"""

import asyncio
import json
import os
import signal
import sys

import pytest

from ceph_tpu.tools import crushtool, ec_benchmark, osdmaptool


def test_crushtool_build_test_decompile(tmp_path, capsys):
    mapfile = str(tmp_path / "cm.bin")
    assert crushtool.main(["--build", "8", "--osds-per-host", "2",
                           "-o", mapfile]) == 0
    assert crushtool.main(["--test", mapfile, "--num-rep", "3",
                           "--max-x", "127", "--json"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    rep = json.loads(out[-1])
    assert rep["inputs"] == 128
    assert rep["result_size_histogram"] == {"3": 128} or \
        rep["result_size_histogram"] == {3: 128}
    assert crushtool.main(["-d", mapfile]) == 0
    out = capsys.readouterr().out
    assert "host host0 {" in out and "rule replicated_rule {" in out


def test_osdmaptool_test_map_pgs(tmp_path, capsys):
    sys.path.insert(0, os.path.dirname(__file__))
    from test_osdmap import build_map
    m = build_map()
    mapfile = str(tmp_path / "om.bin")
    with open(mapfile, "wb") as f:
        f.write(m.to_bytes())
    assert osdmaptool.main([mapfile, "--test-map-pgs", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep["total_pgs"] == 64            # 2 pools x 32
    assert rep["pg_per_osd"]["min"] > 0
    assert osdmaptool.main([mapfile, "--print"]) == 0
    out = capsys.readouterr().out
    assert "pool 1" in out and "osd.0" in out


def test_ec_benchmark_contract(capsys):
    assert ec_benchmark.main(
        ["--plugin", "rs", "--workload", "encode", "--size", "262144",
         "--iterations", "2", "-P", "k=4", "-P", "m=2",
         "-P", "backend=host", "--json"]) == 0
    lines = capsys.readouterr().out.strip().splitlines()
    secs, kib = lines[0].split("\t")       # reference print contract
    assert float(secs) > 0 and int(kib) == 512
    rep = json.loads(lines[1])
    assert rep["k"] == 4 and rep["m"] == 2
    # decode with erasures verifies reconstruction internally
    assert ec_benchmark.main(
        ["--plugin", "rs", "--workload", "decode", "--size", "262144",
         "--iterations", "2", "--erasures", "2", "-P", "k=4", "-P", "m=2",
         "-P", "backend=host"]) == 0


@pytest.mark.slow
def test_vstart_subprocess_cluster(tmp_path):
    """Full operator path with real daemon subprocesses."""
    from ceph_tpu.tools.vstart import VCluster
    from ceph_tpu.tools import rados as rados_cli
    from ceph_tpu.tools import ceph as ceph_cli

    d = str(tmp_path / "cl")
    cl = VCluster(d, n_osds=3, n_mons=1,
                  conf={"osd_heartbeat_grace": "3.0",
                        "mon_osd_down_out_interval": "5.0"})
    cl.write_configs()
    cl.start_daemons()
    try:
        asyncio.run(cl.bootstrap())
        assert ceph_cli.main(["--dir", d, "osd", "pool", "create",
                              "data", "8"]) == 0
        obj = str(tmp_path / "payload")
        with open(obj, "wb") as f:
            f.write(b"vstart-payload" * 100)
        out = str(tmp_path / "out")
        assert rados_cli.main(["--dir", d, "-p", "data", "put", "obj1",
                               obj]) == 0
        assert rados_cli.main(["--dir", d, "-p", "data", "get", "obj1",
                               out]) == 0
        assert open(out, "rb").read() == b"vstart-payload" * 100
        # kill one osd (kill_daemon role); reads must keep working once
        # failure detection + remap kick in
        cl.kill_daemon("osd.2", signal.SIGKILL)

        async def read_until_ok():
            admin = await cl.admin()
            try:
                io = admin.open_ioctx("data")
                deadline = asyncio.get_event_loop().time() + 60
                while True:
                    try:
                        data = await io.read("obj1")
                        return data
                    except Exception:
                        assert asyncio.get_event_loop().time() < deadline
                        await asyncio.sleep(0.5)
            finally:
                await admin.shutdown()
        assert asyncio.run(read_until_ok()) == b"vstart-payload" * 100
    finally:
        cl.stop()


def test_crush_compiler_round_trip(tmp_path):
    """CrushCompiler.cc role: binary -> text -> binary is byte-exact and
    a reference-style handwritten map compiles to working placements."""
    from ceph_tpu.crush.builder import (build_hierarchy, make_erasure_rule,
                                        make_replicated_rule)
    from ceph_tpu.crush.compiler import (CompileError, compile_text,
                                         decompile)
    from ceph_tpu.crush.mapper import do_rule
    from ceph_tpu.crush.types import CrushMap
    import pytest

    m = CrushMap()
    build_hierarchy(m, 12, 3, hosts_per_rack=2)
    make_replicated_rule(m, "replicated_rule")
    make_erasure_rule(m, "ec_rule", size=6)
    text = decompile(m)
    m2 = compile_text(text)
    assert m2.to_bytes() == m.to_bytes(), "round-trip must be byte-exact"
    assert decompile(m2) == text

    # reference-style sample written by hand (straw + uniform + tabs +
    # comments), placements must work and respect the hierarchy
    sample = """
# begin crush map
tunable choose_total_tries 50
tunable chooseleaf_stable 1

# devices
device 0 osd.0
device 1 osd.1
device 2 osd.2
device 3 osd.3

# types
type 0 osd
type 1 host
type 10 root

# buckets
host hostA {
\tid -1
\talg straw2
\thash 0\t# rjenkins1
\titem osd.0 weight 1.000000
\titem osd.1 weight 1.000000
}
host hostB {
\tid -2
\talg straw
\thash 0
\titem osd.2 weight 1.000000
\titem osd.3 weight 2.000000
}
root default {
\tid -3
\talg straw2
\thash 0
\titem hostA weight 2.000000
\titem hostB weight 3.000000
}

# rules
rule replicated_rule {
\truleset 0
\ttype replicated
\tmin_size 1
\tmax_size 10
\tstep take default
\tstep chooseleaf firstn 0 type host
\tstep emit
}
# end crush map
"""
    ms = compile_text(sample)
    assert ms.max_devices == 4
    assert ms.tunables.choose_total_tries == 50
    w = [0x10000] * 4
    hosts = {0: "A", 1: "A", 2: "B", 3: "B"}
    for x in range(64):
        got = do_rule(ms, 0, x, 2, w)
        assert len(got) == 2
        assert hosts[got[0]] != hosts[got[1]], \
            "chooseleaf must spread replicas across hosts"
    # text round-trip of the compiled sample is stable too
    assert compile_text(decompile(ms)).to_bytes() == ms.to_bytes()

    # CLI: crushtool -c / -d round trip through files
    from ceph_tpu.tools.crushtool import main as crushtool_main
    txt_path = tmp_path / "map.txt"
    bin_path = tmp_path / "map.bin"
    txt_path.write_text(text)
    assert crushtool_main(["-c", str(txt_path), "-o", str(bin_path)]) == 0
    assert CrushMap.from_bytes(bin_path.read_bytes()).to_bytes() \
        == m.to_bytes()

    # undefined forward reference fails loudly like the reference
    with pytest.raises(CompileError):
        compile_text("type 0 osd\ntype 10 root\n"
                     "root default { id -1 alg straw2 hash 0 "
                     "item ghost weight 1.000000 }\n")


def test_crush_compiler_single_line_blocks():
    """The reference grammar treats newlines as whitespace: single-line
    bucket/rule blocks must compile."""
    from ceph_tpu.crush.compiler import compile_text
    from ceph_tpu.crush.mapper import do_rule
    one = ("type 0 osd type 1 host type 10 root "
           "device 0 osd.0 device 1 osd.1 "
           "host h { id -1 alg straw2 hash 0 "
           "item osd.0 weight 1.000000 item osd.1 weight 1.000000 } "
           "root default { id -2 alg straw2 hash 0 "
           "item h weight 2.000000 } "
           "rule r { ruleset 0 type replicated min_size 1 max_size 10 "
           "step take default step chooseleaf firstn 0 type osd "
           "step emit }")
    ms = compile_text(one)
    assert ms.max_devices == 2
    got = do_rule(ms, 0, 7, 2, [0x10000] * 2)
    assert sorted(got) == [0, 1]
