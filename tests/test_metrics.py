"""Metrics plane (ISSUE 15): mergeable snapshots, cross-process
histogram round-trips, lane-seam span continuity, the queue-wait cause
taxonomy and the slow-op flight recorder.

The load-bearing property is BIT-FOR-BIT mergeability: a lane worker's
``dump_full`` crosses a ring as JSON bytes, and the parent's
``from_dump`` reconstruction must preserve bucket counts and quantile
interpolation exactly — otherwise the cluster-wide view silently
drifts from the per-process truth.
"""

from __future__ import annotations

import json
import math
import random
import time

import pytest

from ceph_tpu.common import devstats, metrics
from ceph_tpu.common import tracer as tracer_mod
from ceph_tpu.common.context import Context
from ceph_tpu.common.op_tracker import OpTracker
from ceph_tpu.common.perf_counters import PerfCounters, PerfHistogram
from ceph_tpu.common.tracer import (AUX_STAGES, CHAIN_STAGES,
                                    QUEUE_WAIT_CAUSES, Span)

# ===================================== histogram cross-process fidelity


def test_histogram_dump_full_frame_from_dump_roundtrip_bitforbit():
    """dump_full -> json frame -> from_dump preserves buckets, count,
    sum AND quantile interpolation exactly (ints + one float that
    round-trips through repr-based json)."""
    rng = random.Random(15)
    h = PerfHistogram()
    for _ in range(500):
        h.add(rng.uniform(1e-6, 5.0))
    frame = json.dumps(h.dump_full()).encode()     # what crosses a ring
    h2 = PerfHistogram.from_dump(json.loads(frame.decode()))
    assert h2.buckets == h.buckets
    assert h2.count == h.count
    assert h2.sum == h.sum                          # exact, not approx
    for q in (0.5, 0.9, 0.99, 0.999):
        assert h2.quantile(q) == h.quantile(q)      # bit-for-bit


def test_histogram_merge_equals_union():
    a, b = PerfHistogram(), PerfHistogram()
    rng = random.Random(7)
    u = PerfHistogram()
    for _ in range(200):
        s = rng.uniform(1e-6, 0.5)
        (a if rng.random() < 0.5 else b).add(s)
        u.add(s)
    m = PerfHistogram()
    m.merge(PerfHistogram.from_dump(a.dump_full()))
    m.merge(PerfHistogram.from_dump(b.dump_full()))
    assert m.buckets == u.buckets and m.count == u.count
    assert math.isclose(m.sum, u.sum, rel_tol=1e-12)


# ================================================== snapshot + merge


def _ctx(name="osd.0"):
    c = Context(name)
    return c


def test_snapshot_and_merge_sums_counters_and_merges_histograms():
    devstats.reset()
    ctx_a, ctx_b = _ctx("osd.0"), _ctx("osd.1")
    for ctx, n in ((ctx_a, 3), (ctx_b, 5)):
        g = ctx.perf.create("osd")
        g.add_u64("slow_ops")
        g.inc("slow_ops", n)
        g.add_time("commit_lat")
        g.tinc("commit_lat", 0.25 * n)
        st = ctx.perf.create("op_stages")
        for _ in range(n):
            st.hinc("prepare", 0.004)
    devstats.note_bytes("ec_apply", 3000, device=True)
    devstats.note_bytes("ec_apply", 1000, device=False)
    snap_a = metrics.snapshot(ctx_a)
    # a snapshot must survive the wire (ring frame / admin socket)
    snap_b = json.loads(json.dumps(
        metrics.snapshot(ctx_b, source="osd.1/lane0"), default=str))
    assert snap_a["metrics_schema"] == metrics.METRICS_SCHEMA
    assert snap_b["source"] == "osd.1/lane0"
    merged = metrics.merge([snap_a, snap_b])
    assert merged["groups"]["osd"]["slow_ops"] == 8
    assert merged["groups"]["osd"]["commit_lat"]["avgcount"] == 2
    h = PerfHistogram.from_dump(merged["groups"]["op_stages"]["prepare"])
    assert h.count == 8
    # live device_byte_fraction from XFER17-classified byte accounting
    # (both snapshots read the same process-global devstats here, so
    # the merged fraction matches the per-process one)
    assert snap_a["device_byte_fraction"] == 0.75
    assert merged["device_byte_fraction"] == 0.75
    assert merged["sources"] == ["osd.0", "osd.1/lane0"]
    devstats.reset()


def test_merge_carries_lane_dead_loudly():
    merged = metrics.merge([], lane_dead=["osd.0/lane1"])
    assert merged["lane_dead"] == ["osd.0/lane1"]
    txt = metrics.prometheus_text(merged)
    assert "LANE DEAD" in txt and "osd.0/lane1" in txt


def test_prometheus_text_exposition():
    devstats.reset()
    ctx = _ctx()
    g = ctx.perf.create("osd_shard_handoff")
    g.add_u64("handoff_ops")
    g.inc("handoff_ops", 42)
    st = ctx.perf.create("op_stages")
    st.hinc("replica_rtt", 0.010)
    merged = metrics.merge([metrics.snapshot(ctx)])
    txt = metrics.prometheus_text(merged)
    assert "ceph_tpu_osd_shard_handoff_handoff_ops 42" in txt
    assert "ceph_tpu_op_stages_replica_rtt_count 1" in txt
    assert 'quantile="0.99"' in txt
    assert "ceph_tpu_device_byte_fraction" in txt


# ======================================= chain taxonomy + span helpers


def test_chain_declares_lane_and_cause_split_stages():
    for name in ("ring_wait", "lane_codec", "queue_wait_ring",
                 "queue_wait_pump"):
        assert name in CHAIN_STAGES, name
    assert "queue_wait" not in CHAIN_STAGES    # replaced by its causes
    for cause in QUEUE_WAIT_CAUSES:
        assert cause in CHAIN_STAGES, cause
    assert not set(AUX_STAGES) & set(CHAIN_STAGES)


def test_span_attribute_tiles_and_rebase_skips():
    sp = Span(1, 2, "op")
    time.sleep(0.002)
    sp.cut("prepare")
    # explicit-duration attribution advances the cursor to `now`
    t_end = time.monotonic()
    sp.attribute("ring_wait", 0.003)
    sp.attribute("lane_codec", 0.001, now=t_end)
    assert sp._cursor == t_end
    # rebase skips forward without attributing (the lane recorded it)
    time.sleep(0.002)
    anchor = time.monotonic() - 0.0005
    sp.rebase(anchor)
    assert sp._cursor == anchor
    sp.rebase(anchor - 1.0)                     # never moves backward
    assert sp._cursor == anchor
    # a future anchor (offset estimation error) clamps to now: the
    # next cut can never record a negative interval
    sp.rebase(time.monotonic() + 5.0)
    assert sp._cursor <= time.monotonic()
    names = [s for s, _ in sp.stages]
    assert names == ["prepare", "ring_wait", "lane_codec"]
    assert dict(sp.stages)["ring_wait"] == 0.003


def test_lane_envelope_carries_span_context_and_attributes_hop():
    """encode_msg_envelope -> decode_msg_envelope continues the chain
    across the ring: the adopted span starts at the parent's cursor
    and carries ring_wait + lane_codec samples for the hop itself."""
    from ceph_tpu.osd.lanes import (decode_msg_envelope,
                                    encode_msg_envelope)
    from ceph_tpu.osd.messages import MOSDOp
    from ceph_tpu.osd.types import PGId

    ctx = _ctx("osd.7")
    ctx.config.set("op_tracing", True)
    tr = ctx.tracer
    assert tr.enabled

    class _Runtime:
        clock_offset = 0.0
        osd = type("O", (), {"ctx": ctx})

        adopt_lane_span = (
            lambda self, *a: __import__(
                "ceph_tpu.osd.lanes", fromlist=["LaneRuntime"]
            ).LaneRuntime.adopt_lane_span(self, *a))

    m = MOSDOp(PGId(1, 0), "obj", [], tid=9)
    m._span = tr.start("osd_op")
    m._span.cut("deliver")
    body = encode_msg_envelope(m)
    time.sleep(0.002)                            # ring dwell
    got = decode_msg_envelope(body, t_pop=time.monotonic(),
                              runtime=_Runtime())
    sp = got._span
    assert sp is not None
    assert sp.trace_id == m._span.trace_id
    assert sp.span_id == m._span.span_id
    stages = dict(sp.stages)
    assert "ring_wait" in stages and "lane_codec" in stages
    assert stages["ring_wait"] >= 0.001          # the slept dwell
    # the hop tiles: adopted t0 == parent cursor, lane cursor at decode
    # end, and the recorded samples cover the span between them
    hist = tr.hist.histograms()
    assert hist["ring_wait"].count == 1
    assert hist["lane_codec"].count == 1
    # untraced messages stay untraced (no span allocation)
    m2 = MOSDOp(PGId(1, 0), "obj2", [], tid=10)
    got2 = decode_msg_envelope(encode_msg_envelope(m2),
                               t_pop=time.monotonic(),
                               runtime=_Runtime())
    assert got2._span is None


# =========================================== slow-op flight recorder


def test_flight_recorder_records_complaint_and_finish_bounded():
    ot = OpTracker(complaint_time=0.0, flight_recorder_size=4)
    op = ot.create("osd_op(slow)")
    op.span = Span(1, 2, "op")
    op.span.cut("prepare")
    time.sleep(0.001)
    assert ot.check_slow() == 1
    assert ot.check_slow() == 0                  # complains ONCE
    ot.finish(op)
    d = ot.dump_flight_recorder()
    assert d["size"] == 4 and d["num_records"] == 2
    first, last = d["records"][0], d["records"][-1]
    assert first["final"] is False and last["final"] is True
    assert any(s["stage"] == "prepare" for s in last["stages"])
    assert "slow_op_complaint" in last["events"]
    # bounded: the ring never grows past its size
    for i in range(10):
        o = ot.create(f"op{i}")
        o.complained = True                      # simulate complaint
        ot.finish(o)
    assert ot.dump_flight_recorder()["num_records"] == 4


def test_cluster_perf_dump_cli_scrapes_admin_sockets(tmp_path, capsys):
    """`ceph perf dump --cluster`: glob the cluster dir's admin
    sockets, fetch each `perf dump full`, merge — JSON and Prometheus
    forms both carry the summed counters."""
    import asyncio

    from ceph_tpu.common.admin_socket import AdminSocket
    from ceph_tpu.tools.ceph import _cluster_perf_dump

    async def run():
        socks = []
        for name, n in (("mon.a", 2), ("osd.0", 3)):
            ctx = _ctx(name)
            g = ctx.perf.create("osd")
            g.add_u64("slow_ops")
            g.inc("slow_ops", n)
            s = AdminSocket(ctx, str(tmp_path / f"{name}.asok"))
            await s.start()
            socks.append(s)
        loop = asyncio.get_running_loop()
        rc_json = await loop.run_in_executor(
            None, _cluster_perf_dump, str(tmp_path), False)
        rc_prom = await loop.run_in_executor(
            None, _cluster_perf_dump, str(tmp_path), True)
        for s in socks:
            await s.stop()
        return rc_json, rc_prom

    rc_json, rc_prom = asyncio.run(run())
    assert rc_json == 0 and rc_prom == 0
    out = capsys.readouterr().out
    json_part, prom_part = out.split("# ceph-tpu cluster metrics", 1)
    doc = json.loads(json_part)
    assert doc["groups"]["osd"]["slow_ops"] == 5
    assert len(doc["sources"]) == 2
    assert "ceph_tpu_osd_slow_ops 5" in prom_part
    # empty dir: loud failure, not an empty merge
    assert _cluster_perf_dump(str(tmp_path / "nope"), False) == 1


def test_perf_counters_dump_full_groups():
    pc = PerfCounters("g")
    pc.add_u64("n")
    pc.inc("n", 3)
    pc.hinc("lat", 0.002)
    full = pc.dump_full()
    assert full["n"] == 3
    assert "buckets" in full["lat"]
    assert PerfHistogram.from_dump(full["lat"]).count == 1
