"""Striper + RBD block service (ceph_tpu/services/).

Striper unit tests mirror the reference's Striper semantics
(osdc/Striper.h file_to_extents); RBD tests run against live in-process
clusters on replicated AND EC pools (librbd test strategy).
"""

import asyncio
import sys

import numpy as np
import pytest

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from test_osd import Cluster  # noqa: E402

from ceph_tpu.services.striper import (Extent, Layout,  # noqa: E402
                                       file_to_extents)
from ceph_tpu.services.rbd import (RBD, Image, ImageExists,  # noqa: E402
                                   ImageNotFound, RBDError)


# ----------------------------------------------------------------- striper

def test_striper_simple_no_striping():
    # su == object_size, sc=1: plain object split
    lay = Layout(1 << 20, 1, 1 << 20)
    ext = file_to_extents(lay, 0, 3 << 20)
    assert ext == [Extent(0, 0, 1 << 20, 0),
                   Extent(1, 0, 1 << 20, 1 << 20),
                   Extent(2, 0, 1 << 20, 2 << 20)]


def test_striper_round_robin():
    # su=4K, sc=3, os=8K: blocks deal 0,1,2,0,1,2 then next object set
    lay = Layout(4096, 3, 8192)
    ext = file_to_extents(lay, 0, 6 * 4096)
    assert [(e.object_no, e.offset, e.length) for e in ext] == [
        (0, 0, 4096), (1, 0, 4096), (2, 0, 4096),
        (0, 4096, 4096), (1, 4096, 4096), (2, 4096, 4096)]
    # 7th block starts object set 1 -> object_no 3
    ext = file_to_extents(lay, 6 * 4096, 4096)
    assert ext == [Extent(3, 0, 4096, 6 * 4096)]


def test_striper_unaligned_ranges():
    lay = Layout(4096, 2, 16384)
    # every byte maps somewhere exactly once
    total = 100000
    seen = {}
    for e in file_to_extents(lay, 0, total):
        for i in range(e.length):
            key = (e.object_no, e.offset + i)
            assert key not in seen
            seen[key] = e.logical + i
    assert sorted(seen.values()) == list(range(total))
    # an interior unaligned window maps to the same physical bytes
    sub = file_to_extents(lay, 5000, 20000)
    for e in sub:
        for i in range(e.length):
            assert seen[(e.object_no, e.offset + i)] == e.logical + i


def test_striper_merges_contiguous_spans():
    lay = Layout(4096, 1, 4 << 20)   # sc=1: spans in one object merge
    ext = file_to_extents(lay, 0, 1 << 20)
    assert len(ext) == 1 and ext[0].length == 1 << 20


def test_striper_rejects_bad_layout():
    with pytest.raises(ValueError):
        file_to_extents(Layout(4096, 1, 10000), 0, 1)   # os % su != 0


# --------------------------------------------------------------------- rbd

def test_rbd_create_list_info_remove():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        await rbd.create("img1", 8 << 20, order=20)
        await rbd.create("img2", 4 << 20, order=20)
        assert await rbd.list() == ["img1", "img2"]
        with pytest.raises(ImageExists):
            await rbd.create("img1", 1 << 20)
        img = await Image.open(io, "img1")
        st = img.stat()
        assert st["size"] == 8 << 20 and st["object_size"] == 1 << 20
        await rbd.remove("img2")
        assert await rbd.list() == ["img1"]
        with pytest.raises(ImageNotFound):
            await Image.open(io, "img2")
        await cl.stop()
    asyncio.run(run())


def test_rbd_io_replicated_across_object_boundaries():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        await rbd.create("disk", 4 << 20, order=16)   # 64 KiB objects
        img = await Image.open(io, "disk")
        rng = np.random.default_rng(1)
        # write spanning several objects at an unaligned offset
        data = rng.integers(0, 256, 300000, dtype=np.uint8).tobytes()
        off = 12345
        await img.write(off, data)
        assert await img.read(off, len(data)) == data
        # unwritten holes read as zeros
        assert await img.read(0, 100) == b"\x00" * 100
        tail = await img.read(off + len(data), 1000)
        assert tail == b"\x00" * 1000
        # overwrite a window inside
        patch = b"P" * 50000
        await img.write(off + 1000, patch)
        got = await img.read(off, len(data))
        want = bytearray(data)
        want[1000:1000 + len(patch)] = patch
        assert got == bytes(want)
        # writes past the end refuse
        with pytest.raises(RBDError):
            await img.write((4 << 20) - 10, b"x" * 100)
        await cl.stop()
    asyncio.run(run())


def test_rbd_io_on_ec_pool_with_striping():
    async def run():
        cl = Cluster()
        admin = await cl.start(6)
        await admin.pool_create("ecrbd", pg_num=8, pool_type="erasure",
                                k=4, m=2)
        io = admin.open_ioctx("ecrbd")
        rbd = RBD(io)
        # fancy layout: 16K stripe unit over 4 objects of 64K
        await rbd.create("vol", 2 << 20, order=16, stripe_unit=16384,
                         stripe_count=4)
        img = await Image.open(io, "vol")
        rng = np.random.default_rng(2)
        data = rng.integers(0, 256, 200000, dtype=np.uint8).tobytes()
        await img.write(4096, data)                  # EC RMW path
        assert await img.read(4096, len(data)) == data
        patch = rng.integers(0, 256, 30000, dtype=np.uint8).tobytes()
        await img.write(50000, patch)
        got = await img.read(4096, len(data))
        want = bytearray(data)
        want[50000 - 4096:50000 - 4096 + len(patch)] = patch
        assert got == bytes(want)
        await cl.stop()
    asyncio.run(run())


def test_rbd_resize_shrink_drops_objects():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        await rbd.create("disk", 1 << 20, order=16)
        img = await Image.open(io, "disk")
        await img.write(0, b"A" * (1 << 20))
        objs_before = [n for n in await io.list_objects()
                       if n.startswith("rbd_data.")]
        assert len(objs_before) == 16
        await img.resize(128 << 10)                  # shrink to 2 objects
        objs_after = [n for n in await io.list_objects()
                      if n.startswith("rbd_data.")]
        assert len(objs_after) == 2
        img2 = await Image.open(io, "disk")
        assert img2.size == 128 << 10
        assert await img2.read(0, 128 << 10) == b"A" * (128 << 10)
        await cl.stop()
    asyncio.run(run())


def test_rbd_resize_striped_keeps_live_data_and_zeroes_tail():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        # su=4K over 2 objects of 8K: low logical bytes live in BOTH
        # objects of a set — naive per-object shrink would destroy them
        await rbd.create("s", 64 << 10, order=13, stripe_unit=4096,
                         stripe_count=2)
        img = await Image.open(io, "s")
        data = bytes(range(256)) * 64          # 16 KiB
        await img.write(0, data)
        await img.resize(8 << 10)              # keep first 8 KiB
        assert await img.read(0, 8 << 10) == data[:8 << 10]
        # grow back: the dropped tail must read as zeros, not stale bytes
        await img.resize(64 << 10)
        assert await img.read(8 << 10, 8 << 10) == b"\x00" * (8 << 10)
        assert await img.read(0, 8 << 10) == data[:8 << 10]
        await cl.stop()
    asyncio.run(run())


def test_rbd_concurrent_ec_writes_to_one_object_compose():
    async def run():
        cl = Cluster()
        admin = await cl.start(6)
        await admin.pool_create("ec2", pg_num=4, pool_type="erasure",
                                k=4, m=2)
        io = admin.open_ioctx("ec2")
        rbd = RBD(io)
        await rbd.create("v", 1 << 20, order=20)   # ONE object
        img = await Image.open(io, "v")
        # concurrent non-overlapping writes must not lose each other
        writes = [(i * 4096, bytes([i + 1]) * 4096) for i in range(32)]
        await asyncio.gather(*[img.write(off, d) for off, d in writes])
        for off, d in writes:
            assert await img.read(off, 4096) == d, off
        await cl.stop()
    asyncio.run(run())


def test_rbd_cli_and_bench_on_cluster():
    """Operator surface: rbd CLI against a subprocess vstart cluster —
    create/info/bench/export round-trip on an EC pool (VERDICT r2 ask #5:
    'rbd bench numbers on a vstart EC pool')."""
    import os
    import subprocess
    import tempfile
    pytest.importorskip("ceph_tpu.tools.vstart")
    from ceph_tpu.tools.vstart import VCluster
    from ceph_tpu.tools import ceph as ceph_cli
    from ceph_tpu.tools import rbd as rbd_cli
    with tempfile.TemporaryDirectory() as td:
        d = os.path.join(td, "cl")
        cl = VCluster(d, n_osds=6, n_mons=1)
        cl.write_configs()
        cl.start_daemons()
        try:
            asyncio.run(cl.bootstrap())
            assert ceph_cli.main(
                ["--dir", d, "osd", "pool", "create", "rbd", "8",
                 "--type", "erasure", "--k", "4", "--m", "2"]) == 0
            assert rbd_cli.main(
                ["--dir", d, "-p", "rbd", "create", "disk",
                 "--size", "8M", "--order", "18"]) == 0
            assert rbd_cli.main(["--dir", d, "-p", "rbd", "ls"]) == 0
            assert rbd_cli.main(
                ["--dir", d, "-p", "rbd", "bench", "disk",
                 "--io-size", "64K", "--io-total", "1M"]) == 0
            src = os.path.join(td, "src.bin")
            dst = os.path.join(td, "dst.bin")
            with open(src, "wb") as f:
                f.write(bytes(range(256)) * 2048)    # 512 KiB
            assert rbd_cli.main(
                ["--dir", d, "-p", "rbd", "import", src, "vol2",
                 "--order", "16"]) == 0
            assert rbd_cli.main(
                ["--dir", d, "-p", "rbd", "export", "vol2", dst]) == 0
            assert open(dst, "rb").read() == open(src, "rb").read()
        finally:
            cl.stop()


# ------------------------------------------------- snapshots and clones

def test_rbd_snapshot_create_read_rollback_remove():
    """Snap data survives overwrites (RADOS clone-on-write), snap-opened
    handles are read-only, rollback restores, remove trims."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        await rbd.create("disk", 2 << 20, order=16)
        img = await Image.open(io, "disk")
        rng = np.random.default_rng(5)
        v1 = rng.integers(0, 256, 200000, dtype=np.uint8).tobytes()
        await img.write(1000, v1)
        await img.snap_create("s1")
        # image with snapshots refuses removal
        with pytest.raises(RBDError):
            await rbd.remove("disk")
        # overwrite after the snap
        v2 = rng.integers(0, 256, 200000, dtype=np.uint8).tobytes()
        await img.write(1000, v2)
        assert await img.read(1000, len(v2)) == v2
        # the snap still reads v1
        snap = await Image.open(io, "disk", snap_name="s1")
        assert await snap.read(1000, len(v1)) == v1
        from ceph_tpu.services.rbd import ReadOnlyImage
        with pytest.raises(ReadOnlyImage):
            await snap.write(0, b"x")
        await snap.close()
        # a fresh handle sees the snap in the header
        img2 = await Image.open(io, "disk")
        assert [s["name"] for s in img2.snap_list()] == ["s1"]
        # rollback restores v1 on the head
        await img2.snap_rollback("s1")
        assert await img2.read(1000, len(v1)) == v1
        await img2.close()
        await img.close()
        # remove the snap: trim runs, image becomes removable
        img3 = await Image.open(io, "disk")
        await img3.snap_remove("s1")
        assert img3.snap_list() == []
        await img3.close()
        await rbd.remove("disk")
        assert await rbd.list() == []
        await cl.stop()
    asyncio.run(run())


def test_rbd_snapshot_rollback_removes_later_objects():
    # objects written AFTER the snap (absent at snap time) vanish on
    # rollback; size reverts to the snap's size
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        await rbd.create("disk", 1 << 20, order=16)
        img = await Image.open(io, "disk")
        await img.write(0, b"A" * 1000)
        await img.snap_create("s1")
        await img.resize(2 << 20)
        await img.write(1 << 20, b"B" * 1000)   # new object post-snap
        await img.snap_rollback("s1")
        assert img.size == 1 << 20
        assert await img.read(0, 1000) == b"A" * 1000
        await img.close()
        await cl.stop()
    asyncio.run(run())


def test_rbd_snapshot_on_ec_pool():
    async def run():
        cl = Cluster()
        admin = await cl.start(6)
        await admin.pool_create("ecp", pg_num=8, pool_type="erasure",
                                k=2, m=1)
        io = admin.open_ioctx("ecp")
        rbd = RBD(io)
        await rbd.create("disk", 1 << 20, order=16)
        img = await Image.open(io, "disk")
        rng = np.random.default_rng(7)
        v1 = rng.integers(0, 256, 150000, dtype=np.uint8).tobytes()
        await img.write(0, v1)
        await img.snap_create("s1")
        v2 = rng.integers(0, 256, 150000, dtype=np.uint8).tobytes()
        await img.write(0, v2)
        snap = await Image.open(io, "disk", snap_name="s1")
        assert await snap.read(0, len(v1)) == v1
        await snap.close()
        assert await img.read(0, len(v2)) == v2
        await img.close()
        await cl.stop()
    asyncio.run(run())


def test_rbd_clone_copyup_and_flatten():
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        await rbd.create("parent", 1 << 20, order=16)  # 16 objects
        pimg = await Image.open(io, "parent")
        rng = np.random.default_rng(9)
        base = rng.integers(0, 256, 1 << 20, dtype=np.uint8).tobytes()
        await pimg.write(0, base)
        await pimg.snap_create("gold")
        # clone requires a protected snap
        with pytest.raises(RBDError):
            await rbd.clone("parent", "gold", "child")
        await pimg.snap_protect("gold")
        await rbd.clone("parent", "gold", "child")
        assert "child" in await rbd.list()
        assert await rbd.children("parent", "gold") == ["child"]
        # protected snap can't be unprotected while children exist
        with pytest.raises(Exception):
            await pimg.snap_unprotect("gold")
        child = await Image.open(io, "child")
        assert child.parent_info()["image"] == "parent"
        # reads fall through to the parent
        assert await child.read(0, 1 << 20) == base
        # partial write copies the object up, composing with parent data
        await child.write(70000, b"X" * 100)
        want = bytearray(base)
        want[70000:70100] = b"X" * 100
        assert await child.read(0, 1 << 20) == bytes(want)
        # the parent is untouched
        assert await pimg.read(70000, 100) == base[70000:70100]
        # parent writes after the clone don't leak into the child
        await pimg.write(200000, b"Z" * 100)
        assert (await child.read(200000, 100)) == base[200000:200100]
        # flatten severs the lineage; bytes stay identical
        await child.flatten()
        assert child.parent_info() is None
        assert await child.read(0, 1 << 20) == bytes(want)
        assert await rbd.children("parent", "gold") == []
        await pimg.snap_unprotect("gold")   # no children left: allowed
        await child.close()
        await pimg.close()
        await cl.stop()
    asyncio.run(run())


def test_rbd_clone_on_ec_pool_and_discard_no_resurrect():
    async def run():
        cl = Cluster()
        admin = await cl.start(6)
        await admin.pool_create("ecp", pg_num=8, pool_type="erasure",
                                k=2, m=1)
        io = admin.open_ioctx("ecp")
        rbd = RBD(io)
        await rbd.create("parent", 1 << 19, order=16)
        pimg = await Image.open(io, "parent")
        rng = np.random.default_rng(11)
        base = rng.integers(0, 256, 1 << 19, dtype=np.uint8).tobytes()
        await pimg.write(0, base)
        await pimg.snap_create("gold")
        await pimg.snap_protect("gold")
        await rbd.clone("parent", "gold", "child")
        child = await Image.open(io, "child")
        assert await child.read(0, 1 << 19) == base
        # discard inside the overlap must ZERO, not resurrect parent
        await child.discard(0, 1 << 16)     # exactly object 0
        got = await child.read(0, 1 << 17)
        assert got[:1 << 16] == b"\x00" * (1 << 16)
        assert got[1 << 16:] == base[1 << 16:1 << 17]
        # child removal deregisters from the parent
        await child.close()
        await rbd.remove("child")
        assert await rbd.children("parent", "gold") == []
        await pimg.close()
        await cl.stop()
    asyncio.run(run())


def test_rbd_snap_events_replicate_through_mirror():
    """Journaling images replicate snap_create/remove by NAME; the
    secondary allocates its own snap ids."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        await admin.pool_create("rbd_b", pg_num=8)
        io = admin.open_ioctx("rbd")
        dst_io = admin.open_ioctx("rbd_b")
        rbd = RBD(io)
        await rbd.create("disk", 1 << 20, order=16)
        img = await Image.open(io, "disk", journaling=True)
        await img.write(0, b"A" * 1000)
        from ceph_tpu.services.rbd_mirror import ImageReplayer
        rep = ImageReplayer(io, dst_io, "disk")
        await rep.bootstrap()           # full-syncs current content (A)
        # events AFTER bootstrap replay in order: the snap captures A,
        # then B lands on the head
        await img.snap_create("s1")
        await img.write(0, b"B" * 1000)
        await img.close()
        await rep.replay_once()
        mirrored = await Image.open(dst_io, "disk")
        assert [s["name"] for s in mirrored.snap_list()] == ["s1"]
        assert await mirrored.read(0, 1000) == b"B" * 1000
        msnap = await Image.open(dst_io, "disk", snap_name="s1")
        assert await msnap.read(0, 1000) == b"A" * 1000
        await msnap.close()
        await mirrored.close()
        await cl.stop()
    asyncio.run(run())


def test_object_map_tracks_existence_and_serves_clone_reads():
    """librbd ObjectMap feature: exclusive handles maintain a
    per-object existence bitmap; reads consult it (no ENOENT probes)
    and it survives close/reopen."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        await rbd.create("om", 1 << 20, order=16)    # 16 objects
        img = await Image.open(io, "om", exclusive=True)
        assert img.object_map is not None
        await img.write(0, b"A" * 1000)              # object 0
        await img.write(3 << 16, b"B" * 1000)        # object 3
        assert img.object_map.exists(0)
        assert img.object_map.exists(3)
        assert not img.object_map.exists(7)
        # discard of a whole object clears its bit
        await img.discard(3 << 16, 1 << 16)
        assert not img.object_map.exists(3)
        await img.close()                            # persists the map
        img2 = await Image.open(io, "om", exclusive=True)
        assert img2.object_map.exists(0)
        assert not img2.object_map.exists(3)
        assert await img2.read(0, 1000) == b"A" * 1000
        assert await img2.read(3 << 16, 1000) == b"\x00" * 1000
        await img2.close()
        await cl.stop()
    asyncio.run(run())


def test_object_map_invalidated_by_unclean_shutdown():
    """A map left in-use by a crashed holder must NOT be trusted on
    reopen (librbd FLAG_OBJECT_MAP_INVALID): the new holder rebuilds by
    stat scan, so bits the crash never saved are recovered."""
    async def run():
        cl = Cluster()
        admin = await cl.start(3)
        await admin.pool_create("rbd", pg_num=8)
        io = admin.open_ioctx("rbd")
        rbd = RBD(io)
        await rbd.create("cr", 1 << 20, order=16)
        img = await Image.open(io, "cr", exclusive=True)
        await img.write(5 << 16, b"D" * 100)        # object 5
        # CRASH: no close(), no object-map save; drop the lock so the
        # next opener isn't blocked by the TTL
        if img._lock_task:
            img._lock_task.cancel()
        from ceph_tpu.services.rbd import (LOCK_NAME, _cls_unlock,
                                           _client_entity, _header_oid)
        await _cls_unlock(io, _header_oid("cr"), LOCK_NAME,
                          _client_entity(img.io), img._lock_cookie)
        img._lock_cookie = None
        # reopen: the stored map is flagged in-use -> rebuild finds
        # object 5 even though the crash never persisted its bit
        img2 = await Image.open(io, "cr", exclusive=True)
        assert img2.object_map.exists(5), \
            "stale object map trusted after crash"
        assert await img2.read(5 << 16, 100) == b"D" * 100
        await img2.close()
        await cl.stop()
    asyncio.run(run())
